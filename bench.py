"""Benchmark: fused MetricCollection step (update + compute) on one chip.

Headline number tracked against the BASELINE.md north star: the reference's
target is a ``MetricCollection([Accuracy, F1, ...]).compute()`` under 2 ms
(BASELINE.json; the reference itself publishes no absolute numbers — see
BASELINE.md). ``vs_baseline`` is the speedup vs that 2 ms budget (>1 = faster
than target).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Robustness: round 1 emitted no number because the environment-pinned ``axon``
TPU backend died during init; a later run showed init can also *hang*
indefinitely. So the backend is probed in a subprocess with a hard timeout
(a hang can't be cancelled once it's in-process), retried, and on failure the
bench falls back to CPU — a number always lands, and the JSON unit string
records which platform produced it.
"""
import json
import os
import subprocess
import sys
import time

_PROBE_SRC = "import jax; print(jax.devices()[0].platform)"


def _probe_default_backend(timeout_s: float = 150.0, attempts: int = 2):
    """Check, in a throwaway subprocess, that the default backend comes up.

    A *hang* (timeout) forces the CPU fallback immediately: a backend that
    hung once can hang again in-process, where nothing can cancel it and no
    JSON line would ever be emitted. Only clean-but-failed probes are retried.
    """
    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                timeout=timeout_s,
                capture_output=True,
                text=True,
            )
        except subprocess.TimeoutExpired:
            print(f"bench: backend probe hung >{timeout_s}s; not retrying", file=sys.stderr)
            return None
        if proc.returncode == 0 and proc.stdout.strip():
            return proc.stdout.strip().splitlines()[-1]  # plugin chatter may precede it
        print(
            f"bench: backend probe attempt {attempt + 1} failed rc={proc.returncode}: "
            f"{proc.stderr.strip()[-500:]}",
            file=sys.stderr,
        )
    return None


_SYNC_BENCH_SRC = """
from metrics_tpu.utilities.backend import force_cpu_backend
force_cpu_backend(8)
import jax
import time, numpy as np, jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from metrics_tpu.parallel.sync import fused_sync
mesh = Mesh(np.array(jax.devices()), ('data',))
state = {k: jnp.ones((16,), jnp.int32) for k in ('tp', 'fp', 'tn', 'fn')}
def sync_only(s):
    return fused_sync([s], [{k: 'sum' for k in s}], 'data')[0]
fn = jax.jit(jax.shard_map(sync_only, mesh=mesh, in_specs=(P(),), out_specs=P()))
out = fn(state); jax.block_until_ready(out)
iters = 200
t0 = time.perf_counter()
for _ in range(iters):
    out = fn(state)
jax.block_until_ready(out)
print((time.perf_counter() - t0) / iters * 1e6)
"""


_BUCKETED_RANK_SYNC_SRC = """
from metrics_tpu.utilities.backend import force_cpu_backend
force_cpu_backend(8)
import time, numpy as np, jax, jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from metrics_tpu.ops.bucketed_rank import sharded_descending_ranks
mesh = Mesh(np.array(jax.devices()), ('data',))
n = 1_048_576
rng = np.random.default_rng(11)
# 2048-point score grid = one distinct score per histogram bucket, so the
# fused-collective path is exact and parity with the gathered sort is bitwise
x = jnp.asarray((rng.integers(0, 2048, n) / 2048.0).astype(np.float32))
def hist_ranks(s):
    return sharded_descending_ranks(s, 'data')
f_hist = jax.jit(jax.shard_map(hist_ranks, mesh=mesh, in_specs=(P('data'),), out_specs=(P('data'), P())))
def gathered_ranks(s):
    allx = jax.lax.all_gather(s, 'data', tiled=True)
    r = jnp.argsort(jnp.argsort(-allx), stable=True)
    k = s.shape[0]
    return jax.lax.dynamic_slice_in_dim(r, jax.lax.axis_index('data') * k, k)
f_sort = jax.jit(jax.shard_map(gathered_ranks, mesh=mesh, in_specs=(P('data'),), out_specs=P('data')))
g, res = f_hist(x); r = f_sort(x); jax.block_until_ready((g, r))
assert bool(res), 'unresolved buckets on the quantized grid'
assert np.array_equal(np.asarray(g), np.asarray(r).astype(np.int32)), 'PARITY-MISMATCH sharded ranks'
def best(f):
    t = float('inf')
    for _ in range(3):
        t0 = time.perf_counter(); jax.block_until_ready(f(x)); t = min(t, time.perf_counter() - t0)
    return t
print(best(f_hist) * 1e3, best(f_sort) * 1e3)
"""


_T0 = time.time()


def _stamp(tag: str) -> None:
    print(f"bench: [{time.time() - _T0:7.1f}s] {tag}", file=sys.stderr, flush=True)


def _emit(metric: str, value: float, unit: str, vs_baseline=None) -> None:
    print(json.dumps({"metric": metric, "value": value, "unit": unit, "vs_baseline": vs_baseline}))


def _device_loop_ms(jax, step_fn, carry, iters: int) -> float:
    """Per-iteration device time of ``carry -> carry`` via an on-device loop.

    Host-side timing over the axon tunnel is unusable for latency: dispatch
    is fire-and-forget (block_until_ready returns before execution finishes)
    and any result fetch costs a ~70ms round-trip. So the loop runs inside
    one jitted ``fori_loop`` — the chip executes ``iters`` data-dependent
    iterations back-to-back — and the single result fetch at the end
    amortizes to nothing. A 1-iteration run is subtracted as the fixed
    dispatch+fetch baseline.
    """
    import jax.numpy as jnp

    def looped(n, reps=3):
        fn = jax.jit(lambda c: jax.lax.fori_loop(0, n, lambda i, c: step_fn(c), c))
        fn(carry)  # compile + warm
        best = float("inf")
        for _ in range(reps):  # min filters the tunnel's multi-ms jitter
            t0 = time.perf_counter()
            out = fn(carry)
            # fetch one scalar leaf to force completion through the tunnel
            leaf = jax.tree_util.tree_leaves(out)[0]
            float(jnp.asarray(leaf).reshape(-1)[0])
            best = min(best, time.perf_counter() - t0)
        return best

    # Grow the iteration count until the baseline-subtracted delta clears
    # the tunnel's jitter floor — a fixed count floored ssim_512 to 0.0 at
    # the r5 live window (16 iters of a fast kernel < ~ms-scale RTT noise).
    # One growth step sized from the first measured delta (not blind
    # doubling): each looped() call is a fresh compile + 3 tunnel
    # round-trips, so extra probes both cost minutes and raise the odds of
    # a mid-run wedge.
    noise_floor_s = 0.040
    cap = 4096
    base = looped(1)
    full = looped(1 + iters)
    if full - base < noise_floor_s and iters < cap:
        scale = noise_floor_s / max(full - base, noise_floor_s / 64.0)
        iters = min(cap, max(iters + 1, int(iters * scale * 1.5)))
        full = looped(1 + iters)
        # re-sample the baseline after growing (ADVICE r5 #1): a single
        # jitter-inflated looped(1) would otherwise under-report the final
        # value even when the grown delta clears the noise floor
        base = min(base, looped(1))
    if full - base < noise_floor_s:
        print(
            f"bench: WARNING loop delta {full - base:.4f}s below noise floor at "
            f"{iters} iters; value is jitter-dominated, treat as an upper bound",
            file=sys.stderr,
        )
    return max(full - base, 0.0) / iters * 1e3


def _phase_auroc(jax, platform) -> None:
    """AUROC at 1M accumulated samples (CatBuffer capacity mode)."""
    import numpy as np
    import jax.numpy as jnp

    _stamp("auroc_1m start")
    try:
        from metrics_tpu import functionalize, AUROC

        n = 1_000_000
        mdef = functionalize(AUROC(capacity=n))
        rng = np.random.default_rng(0)
        batch_p = jnp.asarray(rng.random(n), jnp.float32)
        batch_t = jnp.asarray(rng.integers(0, 2, n), jnp.int32)
        state = jax.jit(mdef.update)(mdef.init(), batch_p, batch_t)

        def auroc_iter(acc):
            # tiny acc-dependent perturbation: keeps iterations data-dependent
            # (so the on-device loop can't collapse) without moving the value
            st = jax.tree_util.tree_map(
                lambda l: l + (acc * 1e-30).astype(l.dtype) if jnp.issubdtype(l.dtype, jnp.floating) else l,
                state,
            )
            return acc + mdef.compute(st)

        ms = _device_loop_ms(jax, auroc_iter, jnp.asarray(0.0), 8 if platform == "tpu" else 4)
        _emit(
            "auroc_1m_compute_ms",
            round(ms, 4),
            f"ms/compute on-device (exact rank-based AUROC, 1M samples, {platform})",
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: auroc_1m failed: {err}", file=sys.stderr)


def _phase_ssim(jax, platform) -> None:
    """SSIM on 2x3x512x512."""
    import numpy as np
    import jax.numpy as jnp

    _stamp("ssim start")
    try:
        from metrics_tpu.functional import structural_similarity_index_measure

        rng = np.random.default_rng(1)
        a = jnp.asarray(rng.random((2, 3, 512, 512)), jnp.float32)
        b = jnp.asarray(rng.random((2, 3, 512, 512)), jnp.float32)

        def ssim_iter(acc):
            return acc + structural_similarity_index_measure(a + acc * 1e-30, b, data_range=1.0)

        ms = _device_loop_ms(jax, ssim_iter, jnp.asarray(0.0), 16 if platform == "tpu" else 4)
        _emit(
            "ssim_512_ms",
            round(ms, 4),
            f"ms on-device (SSIM 2x3x512x512, {platform})",
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: ssim_512 failed: {err}", file=sys.stderr)


def _phase_retrieval(jax, platform) -> None:
    """100k ragged queries, bucketed vectorized retrieval compute."""
    import numpy as np

    _stamp("retrieval start")
    try:
        from metrics_tpu import RetrievalMAP

        rng = np.random.default_rng(2)
        nq = 100_000
        sizes = rng.integers(5, 50, nq)
        idx = np.repeat(np.arange(nq), sizes)
        preds = rng.random(idx.size).astype(np.float32)
        target = (rng.random(idx.size) < 0.2).astype(np.int64)
        m = RetrievalMAP()
        m.update(preds, target, indexes=idx)
        t0 = time.perf_counter()
        m.compute()
        _emit(
            "retrieval_map_100k_s",
            round(time.perf_counter() - t0, 3),
            f"s/compute (100k ragged queries, {idx.size} docs, {platform})",
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: retrieval_100k failed: {err}", file=sys.stderr)

    # capacity mode: the fully compiled sort+scatter grouped compute that can
    # live inside a jitted step (list mode above is the eager/bucketed path)
    try:
        import jax.numpy as jnp

        from metrics_tpu import RetrievalMAP, functionalize

        nq_c, docs_c = 10_000, 262_144
        idx_c = np.sort(rng.integers(0, nq_c, docs_c)).astype(np.int32)
        preds_c = rng.random(docs_c).astype(np.float32)
        target_c = (rng.random(docs_c) < 0.2).astype(np.float32)
        from metrics_tpu.utilities.ringbuffer import CatBuffer

        mdef = functionalize(RetrievalMAP(capacity=docs_c, num_queries=nq_c, max_docs_per_query=64))
        state = mdef.update(mdef.init(), jnp.asarray(preds_c), jnp.asarray(target_c), indexes=jnp.asarray(idx_c))

        def cap_iter(acc):
            # tie preds AND indexes to the carry so XLA can neither hoist the
            # compute out of the timing loop nor constant-fold the sort/
            # scatter layout stage (the carry contribution is zero at runtime)
            s = dict(state)
            pb, ib = s["preds"], s["indexes"]
            zero_i = (acc * 1e-30).astype(ib.data.dtype)
            s["preds"] = CatBuffer(pb.data + acc * 1e-30, pb.mask, pb.dropped)
            s["indexes"] = CatBuffer(ib.data + zero_i, ib.mask, ib.dropped)
            return acc + mdef.compute(s)

        ms = _device_loop_ms(jax, cap_iter, jnp.asarray(0.0), 8 if platform == "tpu" else 3)
        _emit(
            "retrieval_map_capacity_compiled_ms",
            round(ms, 3),
            f"ms/compute (compiled capacity mode, {nq_c} queries x {docs_c} docs, {platform})",
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: retrieval capacity failed: {err}", file=sys.stderr)


def _phase_bucketed_rank(jax, platform) -> None:
    """Tentpole phase: the packed-radix descending order vs the global
    ``jnp.argsort(-x)`` it replaced in `_binary_clf_curve`/`masked_common`
    (the measured #1 scaling wall, BASELINE.md), at 1M and 10M samples.
    Parity is asserted bitwise before timing. The sharded histogram-rank
    variant (one small collective instead of gather+sort) runs as its own
    8-device CPU-mesh subprocess, like the sync phase."""
    _stamp("bucketed_rank start")
    import numpy as np
    import jax.numpy as jnp

    from metrics_tpu.ops.bucketed_rank import descending_order

    rng = np.random.default_rng(4)
    for n, reps in ((1_000_000, 3), (10_000_000, 2)):
        try:
            x = jnp.asarray(rng.random(n).astype(np.float32))
            f_arg = jax.jit(lambda v: jnp.argsort(-v))
            f_new = jax.jit(descending_order)
            a, b = f_arg(x), f_new(x)
            jax.block_until_ready((a, b))
            if not np.array_equal(np.asarray(a), np.asarray(b)):
                print(f"bench: PARITY-MISMATCH bucketed_rank vs argsort at n={n}", file=sys.stderr)
                continue

            def best(f, x=x, reps=reps):
                t = float("inf")
                for _ in range(reps):
                    t0 = time.perf_counter()
                    jax.block_until_ready(f(x))
                    t = min(t, time.perf_counter() - t0)
                return t

            t_arg, t_new = best(f_arg), best(f_new)
            _emit(
                f"bucketed_rank_{n // 1_000_000}m_ms",
                round(t_new * 1e3, 2),
                f"ms/exact descending order ({n} rows, {platform}); argsort path same data: "
                f"{t_arg * 1e3:.1f} ms",
                round(t_arg / t_new, 2),
            )
        except Exception as err:  # pragma: no cover
            print(f"bench: bucketed_rank n={n} failed: {err}", file=sys.stderr)

    try:
        proc = subprocess.run(
            [sys.executable, "-c", _BUCKETED_RANK_SYNC_SRC],
            timeout=240,
            capture_output=True,
            text=True,
            env=_cpu_env(),
        )
        if proc.returncode == 0 and proc.stdout.strip():
            hist_ms, sort_ms = (float(v) for v in proc.stdout.strip().splitlines()[-1].split())
            _emit(
                "bucketed_rank_sharded_1m_ms",
                round(hist_ms, 2),
                f"ms/exact global ranks (1M rows, 8-device cpu mesh, histogram collective); "
                f"gathered argsort same data: {sort_ms:.1f} ms",
                round(sort_ms / hist_ms, 2),
            )
        else:
            print(f"bench: bucketed_rank sharded rc={proc.returncode}: {proc.stderr[-400:]}", file=sys.stderr)
    except Exception as err:  # pragma: no cover
        print(f"bench: bucketed_rank sharded failed: {err}", file=sys.stderr)


def _phase_guard(jax, platform) -> None:
    """Fault-channel overhead (ISSUE 2 acceptance): the compiled fused step
    (update + compute, the headline step definition) of a guarded metric
    under ``on_invalid='drop'`` must be within 5% of the unguarded
    (``'ignore'``) step. Two views:

    - ``guard_drop_step_ms``: the ACCEPTANCE metric — the capacity-AUROC
      fused update+compute step, 1% NaN rows injected so the masking is
      exercised, not dead code.
    - ``guard_drop_update_ms``: the stricter update-only view. The fault
      masks themselves are ~free (measured 0.004 ms); what shows here is
      the masked-compaction scatter in ``cat_append`` (computed-index
      scatter + cumsum instead of a contiguous slice write), ~+15% of the
      bare ring update on CPU. It is amortized to noise in the fused step
      and is the price of ragged/guarded appends, not of fault counting.
    - ``guard_warn_step_ms``: the stat-scores fused update+compute step
      with counting-only ``'warn'`` (the policy any metric can run traced).

    ``vs_baseline`` is unguarded_time / guarded_time (1.0 = parity, ≥0.95 =
    inside the 5% budget).
    """
    _stamp("guard start")
    import numpy as np
    import jax.numpy as jnp

    from metrics_tpu import AUROC, Accuracy, functionalize

    rng = np.random.default_rng(9)
    iters = 16 if platform == "tpu" else 6

    try:
        cap, batch = 65536, 8192
        p = rng.random(batch).astype(np.float32)
        p[:: max(1, batch // 82)] = np.nan  # ~1% fault rows
        t = (rng.random(batch) < 0.5).astype(np.int32)
        p, t = jnp.asarray(p), jnp.asarray(t)

        def mk_iter(mdef, with_compute):
            state0 = jax.jit(mdef.update)(mdef.init(), p, t)

            def it(carry):
                st, acc = carry
                # tie preds to the carry so the on-device loop stays
                # data-dependent (zero contribution at runtime)
                st = mdef.update(st, p + acc * 1e-30, t)
                bump = mdef.compute(st) if with_compute else st["preds"].dropped.astype(jnp.float32) * 0.0
                return st, acc + bump + 1.0

            return it, (state0, jnp.asarray(0.0))

        for metric_name, with_compute in (("guard_drop_step_ms", True), ("guard_drop_update_ms", False)):
            # alternate the two variants and keep per-variant minima: a
            # single-pass A-then-B comparison at this kernel size reads box
            # jitter (±10% observed) as guard overhead
            times = {"ignore": float("inf"), "drop": float("inf")}
            iters_fns = {
                policy: mk_iter(functionalize(AUROC(capacity=cap, on_invalid=policy)), with_compute)
                for policy in times
            }
            for _ in range(2):
                for policy, (it, carry) in iters_fns.items():
                    times[policy] = min(times[policy], _device_loop_ms(jax, it, carry, iters))
            overhead = times["drop"] / times["ignore"] - 1.0
            what = "fused update+compute step" if with_compute else "ring update only"
            _emit(
                metric_name,
                round(times["drop"], 4),
                f"ms/{what} (capacity AUROC, B={batch}, 1% NaN rows, {platform}); unguarded "
                f"'ignore' same data: {times['ignore']:.4f} ms ({overhead * 100:+.1f}% overhead)",
                round(times["ignore"] / times["drop"], 3),
            )
            if with_compute and overhead > 0.05:
                print(
                    f"bench: GUARD-OVERHEAD drop fused step exceeds the 5% budget: {overhead * 100:.1f}%",
                    file=sys.stderr,
                )
    except Exception as err:  # pragma: no cover
        print(f"bench: guard drop failed: {err}", file=sys.stderr)

    try:
        B, C = 8192, 16
        preds = jnp.asarray(rng.random((B, C)), jnp.float32)
        # target stays a HOST array: inside the on-device loop's trace it is
        # a closure constant, and the canonicalizer's concrete-only checks
        # (checks.py `_is_concrete`) must keep running eagerly on it
        target = rng.integers(0, C, B).astype(np.int32)

        def mk_step_iter(mdef):
            state0 = jax.jit(mdef.update)(mdef.init(), preds, jnp.asarray(target))

            def it(carry):
                st, acc = carry
                st = mdef.update(st, preds + acc * 1e-30, target)
                return st, acc + mdef.compute(st)

            return it, (state0, jnp.asarray(0.0))

        times = {}
        for name, kwargs in (("plain", {}), ("warn", {"on_invalid": "warn"})):
            it, carry = mk_step_iter(functionalize(Accuracy(num_classes=C, **kwargs)))
            times[name] = _device_loop_ms(jax, it, carry, iters)
        overhead = times["warn"] / times["plain"] - 1.0
        _emit(
            "guard_warn_step_ms",
            round(times["warn"], 4),
            f"ms/step (update+compute, Accuracy B={B} C={C}, counting guard, {platform}); "
            f"unguarded same data: {times['plain']:.4f} ms ({overhead * 100:+.1f}% overhead)",
            round(times["plain"] / times["warn"], 3),
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: guard warn failed: {err}", file=sys.stderr)


def _phase_checkpoint(jax, platform) -> None:
    """Snapshot + restore latency of the resilience subsystem (ISSUE 3):
    a guarded 4-metric collection with two non-empty 64k-row CatBuffer ring
    states, saved atomically with per-leaf sha256 checksums and restored
    through full group verification. Restore includes checksum
    re-verification of every leaf — that is the crash-recovery cost being
    measured, not a raw unpickle."""
    _stamp("checkpoint start")
    import shutil
    import tempfile

    import numpy as np
    import jax.numpy as jnp

    import metrics_tpu as mt
    from metrics_tpu.resilience.snapshot import SnapshotManager

    try:
        cap = 1 << 16

        def build():
            return mt.MetricCollection(
                {
                    "auroc": mt.AUROC(capacity=cap, on_invalid="drop"),
                    "ap": mt.AveragePrecision(capacity=cap, on_invalid="drop"),
                    "acc": mt.Accuracy(on_invalid="drop"),
                    "f1": mt.F1Score(on_invalid="drop"),
                }
            )

        coll = build()
        rng = np.random.default_rng(5)
        scores = jnp.asarray(rng.random(cap).astype(np.float32))
        labels = jnp.asarray(rng.integers(0, 2, cap).astype(np.int32))
        for i in range(4):
            sl = slice(i * cap // 4, (i + 1) * cap // 4)
            coll.update(scores[sl], labels[sl])
        before = {k: float(v) for k, v in coll.compute().items()}

        workdir = tempfile.mkdtemp(prefix="bench-ckpt-")
        try:
            mgr = SnapshotManager(workdir, keep=2)
            t_save = float("inf")
            for step in range(2):  # min-of-2 interleave discipline (BASELINE.md)
                t0 = time.perf_counter()
                path = mgr.save(coll, step=step)
                t_save = min(t_save, time.perf_counter() - t0)
            size_mb = os.path.getsize(path) / 1e6
            t_restore = float("inf")
            fresh = None
            for _ in range(2):
                fresh = build()
                t0 = time.perf_counter()
                mgr.restore(fresh)
                t_restore = min(t_restore, time.perf_counter() - t0)
            after = {k: float(v) for k, v in fresh.compute().items()}
            if any(abs(before[k] - after[k]) > 1e-6 for k in before):
                print(f"bench: PARITY-MISMATCH snapshot restore {before} vs {after}", file=sys.stderr)
            _emit(
                "snapshot_save_ms",
                round(t_save * 1e3, 3),
                f"ms/save (guarded 4-metric collection, 2 rings x {cap} rows, "
                f"{size_mb:.2f} MB atomic+checksummed, {platform})",
            )
            _emit(
                "snapshot_restore_ms",
                round(t_restore * 1e3, 3),
                f"ms/restore (newest intact group, every leaf checksum-verified, {platform})",
            )
        finally:
            shutil.rmtree(workdir, ignore_errors=True)
    except Exception as err:  # pragma: no cover
        print(f"bench: checkpoint failed: {err}", file=sys.stderr)


def _phase_sync(jax, platform) -> None:
    """Fused-collection sync us on a virtual 8-device CPU mesh.

    BASELINE.md's tracked sync metric; real multi-chip is unavailable, so
    this runs in a CPU-mesh subprocess — an upper bound on collective count,
    not ICI latency.
    """
    _stamp("sync start")
    try:
        proc = subprocess.run(
            [sys.executable, "-c", _SYNC_BENCH_SRC],
            timeout=300,
            capture_output=True,
            text=True,
            env={**os.environ, "JAX_PLATFORMS": "cpu"},
        )
        if proc.returncode == 0 and proc.stdout.strip():
            _emit(
                "fused_sync_us",
                round(float(proc.stdout.strip().splitlines()[-1]), 2),
                "us/sync (4-state fused psum, 8-device cpu mesh)",
            )
        else:
            print(f"bench: sync bench rc={proc.returncode}: {proc.stderr[-300:]}", file=sys.stderr)
    except Exception as err:  # pragma: no cover
        print(f"bench: sync bench failed: {err}", file=sys.stderr)


def _phase_headline(jax, platform) -> None:
    import jax.numpy as jnp
    import numpy as np

    from __graft_entry__ import entry

    # The legacy (enqueue-throughput) loop MUST run before anything that
    # fetches results: on the axon-tunneled TPU backend the first
    # device->host transfer in a process permanently degrades every later
    # dispatch ~100x (15us -> 1.5ms, measured). block_until_ready does not
    # trigger it. The on-device loop afterwards gives the honest chip time.
    headline = _bench_headline(jax, jnp, np, entry, platform)
    _bench_device_headline(jax, jnp, np, entry, platform)
    print(json.dumps(headline))


# Each phase runs in its own subprocess with a hard timeout: the axon tunnel
# has been observed to hang mid-run (not just at init), and an in-process
# hang can't be cancelled — isolation means a stall loses one line, never
# the whole bench. Budgets are wall-clock seconds per phase.
def _phase_vsref(jax, platform) -> None:
    """Head-to-head wall-clock vs the reference implementation, same data.

    The reference publishes no absolute numbers (SURVEY.md §6), so the honest
    comparison is to run it: torch-CPU torchmetrics (its only execution mode
    in this environment) against this framework end-to-end — host
    preprocessing, transfers, and device compute included. Skipped silently
    when the reference isn't importable.
    """
    _stamp("vsref start")
    import numpy as np

    try:
        import sys as _sys
        import types as _types

        if "pkg_resources" not in _sys.modules:
            try:
                import pkg_resources  # noqa: F401
            except ImportError:
                shim = _types.ModuleType("pkg_resources")
                shim.DistributionNotFound = type("DistributionNotFound", (Exception,), {})
                shim.get_distribution = lambda name: _types.SimpleNamespace(version="0.0.0")
                _sys.modules["pkg_resources"] = shim
        _sys.path.insert(0, "/root/reference/src")
        import torch
        import torchmetrics.functional as RF
    except Exception as err:  # pragma: no cover
        print(f"bench: vsref skipped (reference not importable: {err})", file=sys.stderr)
        return

    # --- WER on 2048 sentence pairs: device wavefront DP vs host python DP
    try:
        from metrics_tpu.functional import word_error_rate

        rng = np.random.default_rng(0)
        vocab = [f"w{i}" for i in range(500)]
        pairs = [
            (
                " ".join(rng.choice(vocab, rng.integers(5, 25))),
                " ".join(rng.choice(vocab, rng.integers(5, 25))),
            )
            for _ in range(2048)
        ]
        preds = [p for p, _ in pairs]
        target = [t for _, t in pairs]

        ours = word_error_rate(preds, target)  # warm compile
        ours_s, ref_s = float("inf"), float("inf")
        for _ in range(3):  # min filters scheduler noise on a loaded box
            t0 = time.perf_counter()
            ours = float(word_error_rate(preds, target))
            ours_s = min(ours_s, time.perf_counter() - t0)
        for _ in range(3):
            t0 = time.perf_counter()
            theirs = float(RF.word_error_rate(preds, target))
            ref_s = min(ref_s, time.perf_counter() - t0)
        assert abs(ours - theirs) < 1e-4, (ours, theirs)
        _emit(
            "wer_2048_pairs_s",
            round(ours_s, 4),
            f"s end-to-end ({platform}); reference torch-cpu same data: {ref_s:.3f}s",
            round(ref_s / ours_s, 2),
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: vsref wer failed: {err}", file=sys.stderr)

    # --- SSIM 4x3x256x256: banded-MXU filtering vs torch-cpu conv
    try:
        import jax.numpy as jnp

        from metrics_tpu.functional import structural_similarity_index_measure

        rng = np.random.default_rng(1)
        a = rng.random((4, 3, 256, 256)).astype(np.float32)
        b = rng.random((4, 3, 256, 256)).astype(np.float32)
        fn = jax.jit(lambda x, y: structural_similarity_index_measure(x, y, data_range=1.0))
        ours = float(fn(jnp.asarray(a), jnp.asarray(b)))  # warm + value
        iters = 5
        t0 = time.perf_counter()
        for _ in range(iters):
            v = fn(jnp.asarray(a), jnp.asarray(b))
        float(v)
        ours_s = (time.perf_counter() - t0) / iters
        ta, tb = torch.from_numpy(a), torch.from_numpy(b)
        theirs = float(RF.structural_similarity_index_measure(ta, tb, data_range=1.0))
        t0 = time.perf_counter()
        for _ in range(iters):
            RF.structural_similarity_index_measure(ta, tb, data_range=1.0)
        ref_s = (time.perf_counter() - t0) / iters
        assert abs(ours - theirs) < 1e-3, (ours, theirs)
        _emit(
            "ssim_256_e2e_s",
            round(ours_s, 4),
            f"s end-to-end incl. h2d+fetch ({platform}); reference torch-cpu same data: {ref_s:.3f}s",
            round(ref_s / ours_s, 2),
        )

        # metric-level accumulation over 8 batches: r5 streaming scalars vs
        # the reference metric's grow-the-image-list-and-concat pattern
        import torchmetrics as RM

        from metrics_tpu import StructuralSimilarityIndexMeasure

        batches = [
            (rng.random((4, 3, 256, 256)).astype(np.float32), rng.random((4, 3, 256, 256)).astype(np.float32))
            for _ in range(8)
        ]
        ours_m = StructuralSimilarityIndexMeasure(data_range=1.0, streaming=True)
        for x, y in batches:  # warm/compile
            ours_m.update(jnp.asarray(x), jnp.asarray(y))
        float(ours_m.compute())
        # min of 2 runs each: the single-sample r5 timing produced a false
        # 0.826x DRIFT flag from scheduler noise (see BASELINE.md)
        ours_stream_s = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            ours_m = StructuralSimilarityIndexMeasure(data_range=1.0, streaming=True)
            for x, y in batches:
                ours_m.update(jnp.asarray(x), jnp.asarray(y))
            ours_val = float(ours_m.compute())
            ours_stream_s = min(ours_stream_s, time.perf_counter() - t0)
        ref_stream_s = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            theirs_m = RM.StructuralSimilarityIndexMeasure(data_range=1.0)
            for x, y in batches:
                theirs_m.update(torch.from_numpy(x), torch.from_numpy(y))
            theirs_val = float(theirs_m.compute())
            ref_stream_s = min(ref_stream_s, time.perf_counter() - t0)
        assert abs(ours_val - theirs_val) < 1e-3, (ours_val, theirs_val)
        _emit(
            "ssim_metric_8batch_s",
            round(ours_stream_s, 4),
            f"s for 8x(4,3,256,256) update+compute, streaming scalars ({platform}); reference "
            f"torch-cpu image-list metric same data: {ref_stream_s:.3f}s",
            round(ref_stream_s / ours_stream_s, 2),
        )
    except AssertionError as err:
        # real value divergence, distinct from import/runtime environment
        # failures (ADVICE r5 #4, same treatment as the retrieval block)
        print(f"bench: PARITY-MISMATCH vsref ssim (ours, reference): {err}", file=sys.stderr)
    except Exception as err:  # pragma: no cover
        print(f"bench: vsref ssim failed: {err}", file=sys.stderr)

    # --- Retrieval MAP over 20k ragged queries: bucketed vectorized grouping
    # vs the reference's host dict loop (one .item() sync per row,
    # reference utilities/data.py:210-233)
    try:
        import jax.numpy as jnp

        import torchmetrics as RM

        from metrics_tpu import RetrievalMAP

        rng = np.random.default_rng(7)
        nq = 20_000
        sizes = rng.integers(5, 30, nq)
        idx = np.repeat(np.arange(nq), sizes)
        preds = rng.random(idx.size).astype(np.float32)
        target = (rng.random(idx.size) < 0.2).astype(np.int64)

        ours_m = RetrievalMAP()
        ours_m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
        float(ours_m.compute())  # warm compile
        t0 = time.perf_counter()
        ours_m = RetrievalMAP()
        ours_m.update(jnp.asarray(preds), jnp.asarray(target), indexes=jnp.asarray(idx))
        ours_val = float(ours_m.compute())
        ours_s = time.perf_counter() - t0

        theirs_m = RM.RetrievalMAP()
        t0 = time.perf_counter()
        theirs_m.update(torch.from_numpy(preds), torch.from_numpy(target), indexes=torch.from_numpy(idx))
        theirs_val = float(theirs_m.compute())
        ref_s = time.perf_counter() - t0
        assert abs(ours_val - theirs_val) < 1e-4, (ours_val, theirs_val)
        _emit(
            "retrieval_map_20k_queries_s",
            round(ours_s, 4),
            f"s update+compute, 20k ragged queries ({platform}); reference torch-cpu dict-loop "
            f"same data: {ref_s:.3f}s",
            round(ref_s / ours_s, 2),
        )
    except AssertionError as err:
        # ADVICE r5 #4: real value divergence must be distinguishable from
        # import/runtime environment failures in the bench log
        print(f"bench: PARITY-MISMATCH vsref retrieval (ours, reference): {err}", file=sys.stderr)
    except Exception as err:  # pragma: no cover
        print(f"bench: vsref retrieval failed: {err}", file=sys.stderr)


def _phase_detection(jax, platform) -> None:
    """COCO mAP at scale: 100 images x 50 boxes, box IoU + greedy matching
    on device (the reference's pycocotools-backed path cannot run here -
    torchvision is absent - so this is a self-number, honestly labeled)."""
    _stamp("detection start")
    import numpy as np

    try:
        from metrics_tpu.detection import MeanAveragePrecision

        rng = np.random.default_rng(0)
        preds, tgts = [], []
        for _ in range(100):
            b = rng.random((50, 4)).astype(np.float32) * 200
            boxes = np.stack([b[:, 0], b[:, 1], b[:, 0] + b[:, 2] / 4 + 5, b[:, 1] + b[:, 3] / 4 + 5], 1)
            preds.append(dict(boxes=boxes, scores=rng.random(50).astype(np.float32), labels=rng.integers(0, 5, 50)))
            tgts.append(dict(boxes=boxes + rng.normal(0, 3, boxes.shape).astype(np.float32), labels=rng.integers(0, 5, 50)))
        warm = MeanAveragePrecision()  # compile the matcher shapes once,
        warm.update(preds, tgts)  # like every other phase's warm pass
        warm.compute()
        best = float("inf")
        for _ in range(3):
            m = MeanAveragePrecision()
            t0 = time.perf_counter()
            m.update(preds, tgts)
            res = m.compute()
            best = min(best, time.perf_counter() - t0)
        _emit(
            "map_100img_50box_s",
            round(best, 3),
            f"s end-to-end warm (COCO mAP, 100 imgs x 50 boxes, 5 classes, {platform}); map={float(res['map']):.4f}",
        )

        # segm: mask IoU as the on-device batched GEMM (round 5) — 40
        # images x 16 instances of 64x64 masks
        s_preds, s_tgts = [], []
        for _ in range(40):
            masks = rng.random((16, 64, 64)) > 0.7
            labels = rng.integers(0, 5, 16)
            s_preds.append(dict(masks=masks, scores=rng.random(16).astype(np.float32), labels=labels))
            # targets = noisy copies (10% pixels flipped) so matches exist
            noisy = masks ^ (rng.random((16, 64, 64)) < 0.1)
            s_tgts.append(dict(masks=noisy, labels=labels))
        warm = MeanAveragePrecision(iou_type="segm")
        warm.update(s_preds, s_tgts)
        warm.compute()
        best_s = float("inf")
        for _ in range(3):
            m = MeanAveragePrecision(iou_type="segm")
            t0 = time.perf_counter()
            m.update(s_preds, s_tgts)
            res_s = m.compute()
            best_s = min(best_s, time.perf_counter() - t0)
        _emit(
            "map_segm_40img_16mask_s",
            round(best_s, 3),
            f"s end-to-end warm (COCO segm mAP, 40 imgs x 16 64x64 masks, device GEMM IoU, {platform});"
            f" map={float(res_s['map']):.4f}",
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: detection failed: {err}", file=sys.stderr)


def _phase_streaming(jax, platform) -> None:
    """Streaming subsystem (ISSUE 4): the windowed wrapper's compiled
    fused update+compute step vs the unwindowed baseline (budget: ≤10%
    overhead — the window must be nearly free before it can be the default
    serving view), and the QuantileSketch at the 1M-row scale the
    acceptance pins (one update folding 1M rows, one sketch merge).

    ``vs_baseline`` on ``windowed_step_ms`` is unwindowed/windowed time
    (≥ 1/1.1 ≈ 0.909 = inside the 10% budget, matching the explicit
    ``overhead > 0.10`` stderr flag below).
    """
    _stamp("streaming start")
    import numpy as np
    import jax.numpy as jnp

    from metrics_tpu import Accuracy, QuantileSketch, WindowedMetric, functionalize

    rng = np.random.default_rng(13)
    iters = 16 if platform == "tpu" else 6

    try:
        B, C, window, buckets = 8192, 16, 65536, 8
        preds = jnp.asarray(rng.random((B, C)), jnp.float32)
        # target stays a HOST array: inside the on-device loop's trace it is
        # a closure constant, and the canonicalizer's concrete-only checks
        # (checks.py) must keep running eagerly on it (same as the guard phase)
        target = rng.integers(0, C, B).astype(np.int32)

        def mk_iter(mdef):
            state0 = jax.jit(mdef.update)(mdef.init(), preds, jnp.asarray(target))

            def it(carry):
                st, acc = carry
                st = mdef.update(st, preds + acc * 1e-30, target)
                return st, acc + mdef.compute(st)

            return it, (state0, jnp.asarray(0.0))

        variants = {
            "plain": functionalize(Accuracy(num_classes=C)),
            "windowed": functionalize(
                WindowedMetric(Accuracy(num_classes=C), window=window, buckets=buckets)
            ),
        }
        # interleaved min-of-2 (BASELINE.md discipline): box jitter at this
        # kernel size reads as wrapper overhead in a single A-then-B pass
        times = {k: float("inf") for k in variants}
        iter_fns = {k: mk_iter(mdef) for k, mdef in variants.items()}
        for _ in range(2):
            for k, (it, carry) in iter_fns.items():
                times[k] = min(times[k], _device_loop_ms(jax, it, carry, iters))
        overhead = times["windowed"] / times["plain"] - 1.0
        _emit(
            "windowed_step_ms",
            round(times["windowed"], 4),
            f"ms/step (update+compute, WindowedMetric(Accuracy) W={window} buckets={buckets}, "
            f"B={B} C={C}, {platform}); unwindowed same data: {times['plain']:.4f} ms "
            f"({overhead * 100:+.1f}% overhead)",
            round(times["plain"] / times["windowed"], 3),
        )
        if overhead > 0.10:
            print(
                f"bench: STREAMING-OVERHEAD windowed step exceeds the 10% budget: "
                f"{overhead * 100:.1f}%",
                file=sys.stderr,
            )
    except Exception as err:  # pragma: no cover
        print(f"bench: streaming windowed failed: {err}", file=sys.stderr)

    try:
        n = 1_048_576
        x = jnp.asarray(rng.random(n).astype(np.float32))
        mdef = functionalize(QuantileSketch(eps=0.01))

        def upd_iter(carry):
            st, acc = carry
            st = mdef.update(st, x + acc * 1e-30)
            return st, acc + st["sketch"].n_seen.astype(jnp.float32) * 0.0 + 1.0

        state0 = jax.jit(mdef.update)(mdef.init(), x)
        t_upd = _device_loop_ms(jax, upd_iter, (state0, jnp.asarray(0.0)), max(2, iters // 2))
        geom = state0["sketch"]
        _emit(
            "qsketch_update_ms",
            round(t_upd, 3),
            f"ms/update (QuantileSketch eps=0.01, 1M rows/batch, "
            f"{geom.items.shape[0]}x{geom.items.shape[1]} levels, {platform})",
        )

        # ISSUE 6 sub-timings: where the update milliseconds go — the
        # binning pre-compaction vs the level-fold cascade
        from metrics_tpu.ops import fold_cascade, precompact_batch

        k = geom.items.shape[1]
        ones = jnp.ones(x.shape, bool)
        inc0, cnt0, level = precompact_batch(x, ones, k)  # eager: level is static

        def best_of(f, *args, reps=3):
            jax.block_until_ready(f(*args))
            t = float("inf")
            for _ in range(reps):
                t0 = time.perf_counter()
                jax.block_until_ready(f(*args))
                t = min(t, time.perf_counter() - t0)
            return t * 1e3

        bin_fn = jax.jit(lambda v: precompact_batch(v, jnp.ones(v.shape, bool), k))
        t_bin = best_of(bin_fn, x)
        compact_fn = jax.jit(
            lambda it, c, i, n: fold_cascade(it, c, i, n, level)
        )
        sk = state0["sketch"]
        t_compact = best_of(compact_fn, sk.items, sk.counts, inc0, cnt0)
        _emit(
            "qsketch_bin_ms",
            round(t_bin, 3),
            f"ms/binned pre-compaction (1M rows -> {inc0.shape[0]} items at level "
            f"{level}, dispatched sketch_precompact kernel, {platform})",
        )
        _emit(
            "qsketch_compact_ms",
            round(t_compact, 3),
            f"ms/fold cascade (level {level} entry, cond-short-circuited, {platform})",
        )

        other = jax.jit(mdef.update)(mdef.init(), 1.0 - x)
        # merge timing: jit the merge directly (carry-independent inputs
        # would be hoisted out of a fori_loop, so time it as a plain call)
        merge_fn = jax.jit(lambda a, b: a.sketch_merge(b))
        merged = merge_fn(state0["sketch"], other["sketch"])
        jax.block_until_ready(merged)
        t_merge = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(merge_fn(state0["sketch"], other["sketch"]))
            t_merge = min(t_merge, time.perf_counter() - t0)
        _emit(
            "qsketch_merge_ms",
            round(t_merge * 1e3, 3),
            f"ms/merge (two 1M-row QuantileSketch states, eps=0.01, {platform})",
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: streaming qsketch failed: {err}", file=sys.stderr)


def _phase_compactor(jax, platform) -> None:
    """ISSUE 6 A/B: the QuantileSketch 1M-row jitted update through the
    legacy full-sort pre-compaction vs the binned-key pass — interleaved
    min-of-2 per variant (BASELINE.md discipline), state parity asserted
    bitwise before timing. A FRESH metric + jit is built per variant: the
    dispatch choice is baked in at trace time, and a shared jit cache
    would silently time one variant twice. Plus the small-batch (512-row)
    update that the cond-short-circuited cascade unlocks (the seed code
    paid the full 20-level fold cascade: ~39 ms measured pre-change)."""
    _stamp("compactor start")
    import numpy as np
    import jax.numpy as jnp

    from metrics_tpu import QuantileSketch, functionalize
    from metrics_tpu.ops import dispatch as kdispatch

    rng = np.random.default_rng(21)
    n = 1_048_576
    x = jnp.asarray(rng.random(n).astype(np.float32))

    try:
        def mk(impl):
            with kdispatch.kernel_override(sketch_precompact=impl):
                mdef = functionalize(QuantileSketch(eps=0.01))
                upd = jax.jit(mdef.update)
                state = upd(mdef.init(), x)  # trace happens under the override
                jax.block_until_ready(state)

            def run(upd=upd, mdef=mdef):
                t0 = time.perf_counter()
                jax.block_until_ready(upd(mdef.init(), x))
                return time.perf_counter() - t0

            return run, state

        runners, states = {}, {}
        for impl in ("sort", "binned"):
            runners[impl], states[impl] = mk(impl)
        same = all(
            np.array_equal(np.asarray(a), np.asarray(b))
            for a, b in zip(
                jax.tree_util.tree_leaves(states["sort"]),
                jax.tree_util.tree_leaves(states["binned"]),
            )
        )
        if not same:
            print("bench: PARITY-MISMATCH compactor sort vs binned state", file=sys.stderr)
        times = {impl: float("inf") for impl in runners}
        for _ in range(2):  # interleaved min-of-2
            for impl, run in runners.items():
                times[impl] = min(times[impl], run())
        _emit(
            "qsketch_update_binned_ms",
            round(times["binned"] * 1e3, 2),
            f"ms/update (QuantileSketch eps=0.01, 1M rows, binned-key pre-compaction, "
            f"{platform}); legacy full-sort path same data: {times['sort'] * 1e3:.1f} ms",
            round(times["sort"] / times["binned"], 2),
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: compactor A/B failed: {err}", file=sys.stderr)

    try:
        xs = jnp.asarray(rng.random(512).astype(np.float32))
        mdef = functionalize(QuantileSketch(eps=0.01))
        upd = jax.jit(mdef.update)
        jax.block_until_ready(upd(mdef.init(), xs))
        t_small = float("inf")
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(upd(mdef.init(), xs))
            t_small = min(t_small, time.perf_counter() - t0)
        _emit(
            "qsketch_smallbatch_update_ms",
            round(t_small * 1e3, 3),
            f"ms/update (QuantileSketch eps=0.01, 512-row batch, cond-short-circuited "
            f"cascade + unpadded precompact, {platform})",
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: compactor small-batch failed: {err}", file=sys.stderr)


def _phase_serving(jax, platform) -> None:
    """Serving hardening (ISSUE 7): per-request update latency of the
    padding-tier ladder under mixed ragged traffic (p50/p99 — tails matter
    on a request path, means hide them), and ``report()`` latency for the
    stale view (the never-blocking serving read) vs a fresh forced reduce.
    Ladder tier graphs are compiled up front, as a warm serving process
    would have them; the jit cache is then asserted to hold exactly
    ``len(ladder)`` entries — the no-unbounded-recompilation contract this
    phase exists to price."""
    _stamp("serving start")
    import numpy as np
    import jax.numpy as jnp

    import metrics_tpu as mt
    from metrics_tpu.ops import padding

    LADDER = (64, 256, 1024)
    os.environ["METRICS_TPU_PAD_LADDER"] = ",".join(str(t) for t in LADDER)
    padding.reset_padding_state()
    rng = np.random.default_rng(17)

    def batch(n):
        return (
            jnp.asarray(rng.random((n, 8), dtype=np.float32)),
            jnp.asarray(rng.integers(0, 8, n).astype(np.int32)),
        )

    try:
        m = mt.Accuracy(num_classes=8, on_invalid="drop", pad_batches=True)
        for tier in LADDER:  # warm every tier graph (a warm serving process)
            p, t = batch(tier)
            m.update(p, t)
            jax.block_until_ready(jax.tree_util.tree_leaves(m.metric_state))

        tiers = {t: [] for t in LADDER}
        spans = {64: (1, 64), 256: (65, 256), 1024: (257, 1024)}
        all_lat = []
        for _ in range(120):
            tier = LADDER[int(rng.integers(0, len(LADDER)))]
            lo, hi = spans[tier]
            p, t = batch(int(rng.integers(lo, hi + 1)))
            t0 = time.perf_counter()
            m.update(p, t)
            jax.block_until_ready(jax.tree_util.tree_leaves(m.metric_state))
            dt = time.perf_counter() - t0
            tiers[tier].append(dt)
            all_lat.append(dt)
        if m._update_jit._cache_size() != len(LADDER):
            print(
                f"bench: PARITY-MISMATCH serving jit cache {m._update_jit._cache_size()} "
                f"graphs != len(ladder) {len(LADDER)}",
                file=sys.stderr,
            )
        per_tier = ", ".join(
            f"tier {t}: p50 {np.percentile(v, 50) * 1e3:.2f} ms" for t, v in tiers.items()
        )
        _emit(
            "serving_update_p50_ms",
            round(float(np.percentile(all_lat, 50)) * 1e3, 3),
            f"ms/request (guarded padded Accuracy, mixed ragged 1-1024 rows, "
            f"ladder {LADDER}, {platform}; {per_tier})",
        )
        _emit(
            "serving_update_p99_ms",
            round(float(np.percentile(all_lat, 99)) * 1e3, 3),
            f"ms/request p99 (same traffic; tail == the request-path promise, {platform})",
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: serving update-latency failed: {err}", file=sys.stderr)

    try:
        # reduce_every_s idles the cadence reducer: fresh reads below must
        # price the FORCED reduce, and a cadence pass covering the last
        # publish first would let report(fresh=True) take its covered-view
        # short circuit and time ~nothing
        with mt.ServeLoop(
            mt.Accuracy(num_classes=8, on_invalid="drop", pad_batches=True),
            workers=2,
            reduce_every_s=3600.0,
        ) as loop:
            for _ in range(100):
                p, t = batch(int(rng.integers(1, 257)))
                loop.offer(p, t)
            loop.drain(120)
            loop.report(fresh=True, deadline_s=10.0)  # materialize a view
            # stale read: the serving-path answer (never blocks on a reduce)
            stale = []
            for _ in range(200):
                t0 = time.perf_counter()
                loop.report()
                stale.append(time.perf_counter() - t0)
            fresh = []
            for _ in range(20):
                # a fresh publish per read: the view is genuinely behind, so
                # each timing covers the full clone+fold+compute pass
                p, t = batch(int(rng.integers(1, 257)))
                loop.offer(p, t)
                loop.drain(120)
                t0 = time.perf_counter()
                view = loop.report(fresh=True, deadline_s=10.0)
                fresh.append(time.perf_counter() - t0)
            loop.stop()
        _emit(
            "serve_report_stale_ms",
            round(float(np.percentile(stale, 50)) * 1e3, 4),
            f"ms/report (stale view, p50 of 200; p99 {np.percentile(stale, 99) * 1e3:.3f} ms, "
            f"{platform})",
        )
        _emit(
            "serve_report_fresh_ms",
            round(float(np.percentile(fresh, 50)) * 1e3, 3),
            f"ms/report (fresh=True forced reduce+compute, p50 of 20, 2 workers, "
            f"{platform}; last fresh={view['fresh']})",
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: serving report-latency failed: {err}", file=sys.stderr)


def _phase_async_sync(jax, platform) -> None:
    """Overlapped async sync (ISSUE 8): p50/p99 ``compute()`` latency on the
    guarded fused 4-metric collection under a simulated training loop,
    blocking vs overlapped, plus the staleness distribution of the
    overlapped reads and a bitwise value-parity check at the end.

    The pod is simulated in-process (this phase runs in its own bench
    child): ``distributed_available`` patched True and a 2-rank transport
    whose per-collective call sleeps 2 ms — conservative vs the ~79 ms PR 7
    measured for one real forced reduce — so the blocking read path pays
    (members x leaves x shape+payload gathers) x 2 ms per compute while the
    overlapped path pays the same gathers on the scheduler thread and reads
    the already-reduced view."""
    _stamp("async_sync start")
    import numpy as np
    import jax.numpy as jnp

    import metrics_tpu as mt
    from metrics_tpu import metric as metric_mod
    from metrics_tpu.parallel.sync import _pad_gather_trim

    GATHER_LATENCY_S = 0.002

    def slow_transport(a):
        time.sleep(GATHER_LATENCY_S)
        arr = np.asarray(a)
        return np.stack([arr, arr])

    def slow_gather(x, group=None, transport=None):
        return _pad_gather_trim(x, slow_transport)

    metric_mod.distributed_available = lambda: True  # child process: isolated

    def make_coll(**kw):
        return mt.MetricCollection(
            {
                "acc": mt.Accuracy(num_classes=8, on_invalid="warn", dist_sync_fn=slow_gather, **kw),
                "prec": mt.Precision(
                    num_classes=8, average="macro", on_invalid="warn", dist_sync_fn=slow_gather, **kw
                ),
                "rec": mt.Recall(
                    num_classes=8, average="macro", on_invalid="warn", dist_sync_fn=slow_gather, **kw
                ),
                "f1": mt.F1Score(
                    num_classes=8, average="macro", on_invalid="warn", dist_sync_fn=slow_gather, **kw
                ),
            }
        )

    rng = np.random.default_rng(23)

    def batch(n=64):
        return (
            jnp.asarray(rng.random((n, 8), dtype=np.float32)),
            jnp.asarray(rng.integers(0, 8, n).astype(np.int32)),
        )

    READS = 50
    stream = [batch() for _ in range(READS + 1)]

    def run_loop(coll, overlapped: bool):
        """The simulated serving/eval loop: update, then read — every read
        timed; staleness (max member lag in steps) recorded per read."""
        coll.update(*stream[0])  # warm: compile graphs, form compute groups
        members = [m for _, m in coll.items(keep_base=True, copy_state=False)]
        if overlapped:
            for m in members:
                m.request_sync(wait=True, deadline_s=60.0)
        jax.block_until_ready(list(coll.compute().values()))  # warm compute graphs
        lat, stale = [], []
        for p, t in stream[1:]:
            coll.update(p, t)
            t0 = time.perf_counter()
            vals = coll.compute()
            jax.block_until_ready(list(vals.values()))
            lat.append(time.perf_counter() - t0)
            if overlapped:
                stale.append(max(m.sync_lag["sync_lag_steps"] for m in members))
        return lat, stale

    try:
        blk_lat, _ = run_loop(make_coll(), overlapped=False)
        ovl_coll = make_coll(sync_mode="overlapped", sync_every_n=1)
        ovl_lat, ovl_stale = run_loop(ovl_coll, overlapped=True)

        # value parity: once every cycle has drained, the overlapped reads
        # must bit-equal a blocking twin fed the identical stream
        members = [m for _, m in ovl_coll.items(keep_base=True, copy_state=False)]
        for m in members:
            m.request_sync(wait=True, deadline_s=60.0)
        ovl_vals = ovl_coll.compute()
        ref = make_coll()
        for p, t in stream:
            ref.update(p, t)
        ref_vals = ref.compute()
        for key, v in ovl_vals.items():
            if float(v) != float(ref_vals[key]):
                print(
                    f"bench: PARITY-MISMATCH async_sync {key}: overlapped {float(v)} "
                    f"!= blocking {float(ref_vals[key])}",
                    file=sys.stderr,
                )

        blk_p50, blk_p99 = (float(np.percentile(blk_lat, q)) for q in (50, 99))
        ovl_p50, ovl_p99 = (float(np.percentile(ovl_lat, q)) for q in (50, 99))
        _emit(
            "async_compute_blocking_p50_ms",
            round(blk_p50 * 1e3, 3),
            f"ms/compute (guarded fused 4-metric collection, blocking sync, simulated "
            f"2-rank pod at {GATHER_LATENCY_S * 1e3:.0f} ms/gather, {platform}; "
            f"p99 {blk_p99 * 1e3:.1f} ms)",
        )
        _emit(
            "async_compute_overlapped_p50_ms",
            round(ovl_p50 * 1e3, 3),
            f"ms/compute (same collection, sync_mode='overlapped' n=1 — the "
            f"zero-collective stale read, {platform}; p99 {ovl_p99 * 1e3:.1f} ms)",
        )
        _emit(
            "async_compute_overlapped_p99_ms",
            round(ovl_p99 * 1e3, 3),
            f"ms/compute p99 (acceptance: <= 0.1x blocking p99 {blk_p99 * 1e3:.1f} ms "
            f"-> ratio {ovl_p99 / blk_p99:.4f}, {platform})",
        )
        _emit(
            "async_staleness_steps_p50",
            round(float(np.percentile(ovl_stale, 50)), 1),
            f"update-steps behind live at read time (p99 "
            f"{np.percentile(ovl_stale, 99):.0f}; bounded by one in-flight cycle per "
            f"collection — a single issuer thread, {platform})",
        )
        if ovl_p99 > 0.1 * blk_p99:
            print(
                f"bench: PARITY-MISMATCH async_sync acceptance: overlapped p99 "
                f"{ovl_p99 * 1e3:.2f} ms > 0.1x blocking p99 {blk_p99 * 1e3:.2f} ms",
                file=sys.stderr,
            )
    except Exception as err:  # pragma: no cover
        print(f"bench: async_sync failed: {err}", file=sys.stderr)


def _phase_obs(jax, platform) -> None:
    """Observability overhead (ISSUE 10): the warm compiled guarded fused
    4-metric update+compute step timed three ways — UNINSTRUMENTED (span
    call sites patched to no-ops: the pre-ISSUE-10 baseline), tracing
    DISABLED (the default: every span call takes the amortized-env no-op
    path), tracing ENABLED (ring + sketch-histogram sink live). Acceptance:
    disabled ≤1% over uninstrumented, enabled ≤5%. Plus the per-span micro
    costs and one full Prometheus scrape render."""
    _stamp("obs start")
    import numpy as np
    import jax.numpy as jnp

    import metrics_tpu as mt
    from metrics_tpu.obs import export as obs_export
    from metrics_tpu.obs import trace as obs_trace
    from metrics_tpu.obs.trace import _NOOP_SPAN

    rng = np.random.default_rng(29)
    preds = jnp.asarray(rng.random((8192, 16), dtype=np.float32))
    target = jnp.asarray(rng.integers(0, 16, 8192).astype(np.int32))
    coll = mt.MetricCollection(
        {
            "acc": mt.Accuracy(num_classes=16, on_invalid="warn"),
            "prec": mt.Precision(num_classes=16, average="macro", on_invalid="warn"),
            "rec": mt.Recall(num_classes=16, average="macro", on_invalid="warn"),
            "f1": mt.F1Score(num_classes=16, average="macro", on_invalid="warn"),
        }
    )
    coll.update(preds, target)
    jax.block_until_ready(list(coll.compute().values()))  # warm every graph

    def step_ms(samples=40, batch=5):
        best = float("inf")
        for _ in range(samples):
            t0 = time.perf_counter()
            for _ in range(batch):
                coll.update(preds, target)
                vals = coll.compute()
            jax.block_until_ready(list(vals.values()))
            best = min(best, time.perf_counter() - t0)
        return best / batch * 1e3

    def span_ns(samples=30, batch=2000):
        best = float("inf")
        for _ in range(samples):
            t0 = time.perf_counter()
            for _ in range(batch):
                with obs_trace.span("bench.probe", metric="X"):
                    pass
            best = min(best, time.perf_counter() - t0)
        return best / batch * 1e9

    try:
        # uninstrumented baseline: the span/instant call sites become bare
        # no-op calls (what the runtime paid before this layer existed)
        real_span, real_instant = obs_trace.span, obs_trace.instant
        obs_trace.span = lambda name, **attrs: _NOOP_SPAN
        obs_trace.instant = lambda name, **attrs: None
        try:
            base_ms = step_ms()
        finally:
            obs_trace.span, obs_trace.instant = real_span, real_instant

        disabled_ms = step_ms()
        disabled_span_ns = span_ns()
        with obs_trace.force_tracing(True):
            enabled_ms = step_ms()
            enabled_span_ns = span_ns()
            t0 = time.perf_counter()
            scrape = obs_export.prometheus_text(health=mt.health_report(coll))
            scrape_ms = (time.perf_counter() - t0) * 1e3
        obs_trace.clear_trace()

        disabled_pct = (disabled_ms - base_ms) / base_ms * 100
        enabled_pct = (enabled_ms - base_ms) / base_ms * 100
        _emit(
            "obs_step_uninstrumented_ms",
            round(base_ms, 4),
            f"ms/step (guarded fused 4-metric update+compute, B=8192 C=16, span "
            f"sites patched out, {platform})",
        )
        _emit(
            "obs_overhead_disabled_pct",
            round(disabled_pct, 3),
            f"% over uninstrumented (tracing disabled — the default; budget <=1%, "
            f"{disabled_span_ns:.0f} ns/span, {platform})",
        )
        _emit(
            "obs_overhead_enabled_pct",
            round(enabled_pct, 3),
            f"% over uninstrumented (METRICS_TPU_TRACE=1: ring + sketch-histogram "
            f"sink; budget <=5%, {enabled_span_ns:.0f} ns/span, {platform})",
        )
        _emit(
            "obs_scrape_ms",
            round(scrape_ms, 3),
            f"ms/scrape (Prometheus render over health_report + {len(scrape)} B of "
            f"text, numpy quantile path, {platform})",
        )
        if disabled_pct > 1.0:
            print(
                f"bench: PARITY-MISMATCH obs acceptance: disabled overhead "
                f"{disabled_pct:.2f}% > 1%",
                file=sys.stderr,
            )
        if enabled_pct > 5.0:
            print(
                f"bench: PARITY-MISMATCH obs acceptance: enabled overhead "
                f"{enabled_pct:.2f}% > 5%",
                file=sys.stderr,
            )
    except Exception as err:  # pragma: no cover
        print(f"bench: obs overhead failed: {err}", file=sys.stderr)


def _phase_transport(jax, platform) -> None:
    """Quantized sync transport (ISSUE 12): payload bytes + end-to-end cycle
    latency for exact vs fp16 vs int8 on a simulated 2-rank pod whose
    gather is DCN-shaped (fixed RTT + bytes/bandwidth — so payload bytes
    ARE latency), plus the fleet view blob bytes exact vs int8.

    The workload is the stated customer: an overlapped QuantileSketch
    metric (double-buffered cycles ship the full sketch state per cycle).
    Arms run interleaved (same thermal/jitter per rep), min over the reps
    per arm; the exact arm carries a bit-exactness assert against a
    blocking twin fed the identical stream.
    """
    _stamp("transport start")
    import numpy as np
    import jax.numpy as jnp

    import metrics_tpu as mt
    from metrics_tpu import metric as metric_mod
    from metrics_tpu.obs.runtime_metrics import registry as obs_registry
    from metrics_tpu.parallel.sync import _pad_gather_trim

    # DCN shape: 0.5 ms fixed RTT per collective + 25 MB/s effective
    # per-flow bandwidth (congested cross-region DCN) — the regime the
    # ROADMAP names, where the ~250 KB f32 sketch payload costs ~10 ms of
    # pure byte time per gather and transport width prices directly into
    # cycle latency
    BASE_RTT_S = 0.0005
    BYTES_PER_S = 25e6

    def dcn_transport(a):
        arr = np.asarray(a)
        time.sleep(BASE_RTT_S + arr.nbytes / BYTES_PER_S)
        return np.stack([arr, arr])

    def dcn_gather(x, group=None, transport=None):
        return _pad_gather_trim(x, dcn_transport)

    metric_mod.distributed_available = lambda: True  # child process: isolated

    # wide-and-flat geometry: ~256 KB of items at only 4 compactor levels,
    # so the host-side merge floor stays small relative to the wire time
    # this phase exists to price (error contract unchanged: eps is stated)
    QS = dict(eps=0.01, k=16384, levels=4, quantiles=(0.5, 0.99))

    def make(transport):
        return mt.QuantileSketch(
            **QS,
            sync_mode="overlapped",
            sync_every_n=1,
            sync_transport=transport,
            dist_sync_fn=dcn_gather,
        )

    rng = np.random.default_rng(31)
    stream = [jnp.asarray(rng.lognormal(0, 2, 4096).astype(np.float32)) for _ in range(8)]

    try:
        arms = ("exact", "fp16", "int8")
        metrics = {arm: make(arm) for arm in arms}
        for arm in arms:  # warm: one covered cycle each (compile + trace)
            metrics[arm].update(stream[0])
            assert metrics[arm].request_sync(wait=True, deadline_s=60.0)
        lat = {arm: [] for arm in arms}
        cycle_bytes = {arm: [] for arm in arms}
        for rep, batch in enumerate(stream[1:]):
            for arm in arms:  # interleaved: same thermal/jitter per rep
                m = metrics[arm]
                b0 = obs_registry.counter("sync_payload_bytes").value
                m.update(batch)
                t0 = time.perf_counter()
                ok = m.request_sync(wait=True, deadline_s=60.0)
                lat[arm].append(time.perf_counter() - t0)
                cycle_bytes[arm].append(obs_registry.counter("sync_payload_bytes").value - b0)
                if not ok:
                    print(f"bench: transport arm {arm} cycle uncovered", file=sys.stderr)

        # exactness assert on the exact arm: bit-equal to a blocking twin
        twin = mt.QuantileSketch(**QS, dist_sync_fn=dcn_gather)
        for batch in stream:
            twin.update(batch)
        exact_vals = np.asarray(metrics["exact"].compute())
        twin_vals = np.asarray(twin.compute())
        if not np.array_equal(exact_vals, twin_vals):
            print(
                f"bench: PARITY-MISMATCH transport exact arm {exact_vals} != "
                f"blocking twin {twin_vals}",
                file=sys.stderr,
            )
        for m in metrics.values():
            m._ensure_sync_scheduler().stop()

        by = {arm: float(np.median(cycle_bytes[arm])) for arm in arms}
        best = {arm: float(np.min(lat[arm])) * 1e3 for arm in arms}  # min over reps
        for arm in arms:
            _emit(
                f"transport_cycle_{arm}_ms",
                round(best[arm], 3),
                f"ms/overlapped cycle end-to-end ({QS['eps']}-eps sketch state, "
                f"simulated 2-rank pod, {BASE_RTT_S * 1e3:.1f} ms RTT + "
                f"{BYTES_PER_S / 1e6:.0f} MB/s DCN-shaped gather, "
                f"min-of-{len(stream) - 1}, {by[arm] / 1024:.0f} KiB/cycle, {platform})",
            )
        _emit(
            "transport_sync_bytes_ratio_int8",
            round(by["exact"] / by["int8"], 2),
            f"x fewer gathered payload bytes per cycle vs exact f32 "
            f"({by['exact'] / 1024:.0f} -> {by['int8'] / 1024:.0f} KiB; acceptance >= 3x, "
            f"fp16 {by['exact'] / by['fp16']:.2f}x, {platform})",
        )
        if by["exact"] / by["int8"] < 3.0:
            print(
                f"bench: PARITY-MISMATCH transport acceptance: int8 byte ratio "
                f"{by['exact'] / by['int8']:.2f} < 3x",
                file=sys.stderr,
            )

        # fleet blob bytes: the same sketch state as a published host view
        from metrics_tpu.fleet.wire import encode_view

        payload = twin.snapshot_state()
        blob_exact = encode_view(payload, host_id="bench", seq=1)
        blob_int8 = encode_view(payload, host_id="bench", seq=2, encoding="int8")
        _emit(
            "transport_fleet_blob_ratio_int8",
            round(len(blob_exact) / len(blob_int8), 2),
            f"x smaller fleet view blob under int8-zlib-v1 "
            f"({len(blob_exact) / 1024:.0f} -> {len(blob_int8) / 1024:.1f} KiB; "
            f"acceptance >= 3x, {platform})",
        )
        if len(blob_exact) / len(blob_int8) < 3.0:
            print(
                f"bench: PARITY-MISMATCH transport acceptance: fleet blob ratio "
                f"{len(blob_exact) / len(blob_int8):.2f} < 3x",
                file=sys.stderr,
            )
    except Exception as err:  # pragma: no cover
        print(f"bench: transport failed: {err}", file=sys.stderr)


def _phase_coldstart(jax, platform) -> None:
    """Serving cold start (ISSUE 13): the first-request latency wall the
    steady-state serving numbers never show. Three measurements:

    - **cold vs warmed first request** per ladder tier: a fresh metric's
      first update at a tier pays trace + lower + XLA compile; a
      warmup-installed clone's first update calls a ready AOT executable.
      p50/p99 over fresh instances (each rep is a genuine first touch —
      fresh jit objects, shared warmed tables). The acceptance ratio is
      cold/warmed at the TOP tier (>= 10x).
    - **warmup wall time**: what the background thread spends compiling the
      whole matrix (the cost serving never waits on).
    - **warm-restart compile count**: two subprocesses against one
      METRICS_TPU_COMPILE_CACHE_DIR — the second must compile 0 graphs
      (counted via jax.monitoring cache hit/miss events).
    """
    _stamp("coldstart start")
    import copy

    import numpy as np

    import metrics_tpu as mt
    from metrics_tpu.ops import padding
    from metrics_tpu.serving.warmup import Warmup, WarmupEngine, reset_warmup_state

    LADDER = (64, 256, 1024)
    os.environ["METRICS_TPU_PAD_LADDER"] = ",".join(str(t) for t in LADDER)
    os.environ.pop("METRICS_TPU_COMPILE_CACHE_DIR", None)  # honest in-process colds
    padding.reset_padding_state()
    reset_warmup_state()
    import jax.numpy as jnp

    rng = np.random.default_rng(23)

    def batch(n):
        return (
            jnp.asarray(rng.random((n, 8), dtype=np.float32)),
            jnp.asarray(rng.integers(0, 8, n).astype(np.int32)),
        )

    def proto():
        return mt.Accuracy(num_classes=8, on_invalid="drop", pad_batches=True)

    REPS = 7
    try:
        cold = {t: [] for t in LADDER}
        for tier in LADDER:
            for _ in range(REPS):
                m = proto()  # fresh jit: a genuinely cold tier
                p, t = batch(tier)
                t0 = time.perf_counter()
                m.update(p, t)
                jax.block_until_ready(jax.tree_util.tree_leaves(m.metric_state))
                cold[tier].append(time.perf_counter() - t0)

        base = proto()
        spec = Warmup(
            example_args=(np.zeros((16, 8), np.float32), np.zeros((16,), np.int32)),
            max_rows=LADDER[-1],
        )
        engine = WarmupEngine(base, spec)
        t0 = time.perf_counter()
        engine.start()
        if not engine.wait(timeout_s=240) or engine.state()["status"] != "done":
            raise RuntimeError(f"warmup did not finish: {engine.state()}")
        warmup_wall = time.perf_counter() - t0

        warmed = {t: [] for t in LADDER}
        for tier in LADDER:
            for _ in range(REPS):
                m = copy.deepcopy(base)  # fresh instance, shared warmed tables
                m.reset()
                engine.install(m)
                p, t = batch(tier)
                t0 = time.perf_counter()
                m.update(p, t)
                jax.block_until_ready(jax.tree_util.tree_leaves(m.metric_state))
                warmed[tier].append(time.perf_counter() - t0)
                if m._update_jit.aot_misses:
                    print(
                        f"bench: PARITY-MISMATCH coldstart tier {tier} missed the "
                        "warmed table (measured the jit path, not the executable)",
                        file=sys.stderr,
                    )

        top = LADDER[-1]
        cold_p99 = float(np.percentile(cold[top], 99)) * 1e3
        warm_p99 = float(np.percentile(warmed[top], 99)) * 1e3
        per_tier = ", ".join(
            f"tier {t}: {np.percentile(cold[t], 50) * 1e3:.0f} -> "
            f"{np.percentile(warmed[t], 50) * 1e3:.2f} ms p50"
            for t in LADDER
        )
        _emit(
            "coldstart_first_request_cold_p99_ms",
            round(cold_p99, 2),
            f"ms first request, tier {top} COLD (trace+lower+compile on the request "
            f"path; {per_tier}; {platform})",
        )
        _emit(
            "coldstart_first_request_warmed_p99_ms",
            round(warm_p99, 3),
            f"ms first request, tier {top} after AOT warmup (ready executable; "
            f"acceptance >= 10x vs cold, measured {cold_p99 / warm_p99:.0f}x; {platform})",
        )
        if cold_p99 / warm_p99 < 10.0:
            print(
                f"bench: PARITY-MISMATCH coldstart acceptance: cold/warmed p99 ratio "
                f"{cold_p99 / warm_p99:.1f} < 10x at tier {top}",
                file=sys.stderr,
            )
        _emit(
            "coldstart_warmup_wall_s",
            round(warmup_wall, 2),
            f"s background warmup wall time ({engine.graphs_compiled} graphs, ladder "
            f"{LADDER} x guarded Accuracy + compute, {platform})",
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: coldstart first-request failed: {err}", file=sys.stderr)

    try:
        import tempfile

        child_src = (
            "import json\n"
            "import numpy as np\n"
            "import jax, jax.numpy as jnp\n"
            "events = {'hits': 0, 'misses': 0}\n"
            "def _l(name, **kw):\n"
            "    if name == '/jax/compilation_cache/cache_hits': events['hits'] += 1\n"
            "    elif name == '/jax/compilation_cache/cache_misses': events['misses'] += 1\n"
            "jax.monitoring.register_event_listener(_l)\n"
            "import metrics_tpu as mt\n"
            "proto = mt.Accuracy(num_classes=8, on_invalid='drop', pad_batches=True)\n"
            "spec = mt.Warmup(example_args=(np.zeros((16, 8), np.float32),"
            " np.zeros((16,), np.int32)), max_rows=1024)\n"
            "with mt.ServeLoop(proto, workers=1, warmup=spec) as loop:\n"
            "    assert loop.wait_warmup(timeout_s=180)\n"
            "    rng = np.random.default_rng(0)\n"
            "    for n in (5, 100, 700):\n"
            "        loop.offer(jnp.asarray(rng.random((n, 8), dtype=np.float32)),\n"
            "                   jnp.asarray(rng.integers(0, 8, n).astype(np.int32)))\n"
            "    loop.drain(60)\n"
            "print(json.dumps(events))\n"
        )
        with tempfile.TemporaryDirectory() as cache_dir:
            env = _cpu_env()
            env["METRICS_TPU_PAD_LADDER"] = ",".join(str(t) for t in LADDER)
            env["METRICS_TPU_COMPILE_CACHE_DIR"] = cache_dir
            runs = []
            for _ in range(2):
                proc = subprocess.run(
                    [sys.executable, "-c", child_src],
                    timeout=300,
                    capture_output=True,
                    text=True,
                    env=env,
                    cwd=os.path.dirname(os.path.abspath(__file__)),
                )
                if proc.returncode != 0:
                    raise RuntimeError(f"coldstart child failed: {proc.stderr[-800:]}")
                runs.append(json.loads(proc.stdout.strip().splitlines()[-1]))
        _emit(
            "coldstart_warm_restart_compiles",
            runs[1]["misses"],
            f"XLA compiles in a RESTARTED process sharing the persistent compile "
            f"cache (first run compiled {runs[0]['misses']}, restart read "
            f"{runs[1]['hits']} cache hits; acceptance == 0; {platform})",
        )
        if runs[1]["misses"] != 0:
            print(
                f"bench: PARITY-MISMATCH coldstart warm restart compiled "
                f"{runs[1]['misses']} graphs (expected 0)",
                file=sys.stderr,
            )
    except Exception as err:  # pragma: no cover
        print(f"bench: coldstart warm-restart failed: {err}", file=sys.stderr)


def _phase_overlap(jax, platform) -> None:
    """Chunked gather overlap (ISSUE 16): the host-tier issue/fold pipeline
    priced on its stated customer. Each job is ``Metric._gathered_state``'s
    sketch job verbatim — ``issue`` gathers every leaf of a real seeded
    0.01-eps QuantileSketch state over a simulated 2-rank DCN-shaped
    transport (fixed RTT + bytes/bandwidth), ``fold`` rebuilds the per-rank
    sketches and merges them through ``sketch_merge`` (the ~30 ms host-side
    compactor run) — and a K-job sequence runs through ``run_gather_jobs``
    both ways: sequential (fold i completes before issue i+1 starts, the
    pre-ISSUE-16 schedule) and pipelined (issues on the daemon thread,
    folds one job behind on the caller). Issue order is identical in both
    modes (the cross-host collective pairing contract), so the only
    variable is whether fold compute hides wire time. Acceptance: the
    pipelined wall recovers >= 30% of the sequential wall, and the folded
    merges are bit-equal between the two modes."""
    _stamp("overlap start")
    import numpy as np
    import jax.numpy as jnp

    import metrics_tpu as mt
    from metrics_tpu.parallel.sync import run_gather_jobs

    # DCN shape: 0.5 ms RTT per gather + 8 MB/s effective per-flow
    # bandwidth (the heavily-congested tail of the cross-region regime the
    # transport phase prices at 25 MB/s) — a ~256 KiB sketch state costs
    # ~34 ms of wire per job, comparable to its ~30 ms merge fold, the
    # regime where overlapping the two halves pays
    BASE_RTT_S = 0.0005
    BYTES_PER_S = 8e6
    JOBS = 8
    RANKS = 2

    def dcn_transport(a):
        arr = np.asarray(a)
        time.sleep(BASE_RTT_S + arr.nbytes / BYTES_PER_S)
        return np.stack([arr] * RANKS)

    def make_state(seed):
        m = mt.QuantileSketch(eps=0.01, k=16384, levels=4, quantiles=(0.5, 0.99))
        r = np.random.default_rng(seed)
        for _ in range(4):
            m.update(jnp.asarray(r.lognormal(0, 2, 8192).astype(np.float32)))
        return m._state["sketch"]

    states = [make_state(seed) for seed in range(JOBS)]
    state_bytes = sum(np.asarray(x).nbytes for x in jax.tree_util.tree_leaves(states[0]))

    def make_jobs():
        jobs = []
        for i, st in enumerate(states):
            leaves, treedef = jax.tree_util.tree_flatten(st)

            def issue(leaves=leaves):
                return [dcn_transport(leaf) for leaf in leaves]

            def fold(gathered, treedef=treedef):
                ranks = [
                    jax.tree_util.tree_unflatten(treedef, [g[r] for g in gathered])
                    for r in range(RANKS)
                ]
                merged = ranks[0]
                for other in ranks[1:]:
                    merged = merged.sketch_merge(other)
                jax.block_until_ready(jax.tree_util.tree_leaves(merged))
                return merged

            jobs.append((f"sketch_{i}", issue, fold))
        return jobs

    try:
        warm = make_jobs()[0]  # compile the merge graph outside the timing
        warm[2](warm[1]())
        walls = {False: [], True: []}
        outs = {}
        for _rep in range(3):
            for pipeline in (False, True):  # interleaved: same jitter per rep
                t0 = time.perf_counter()
                outs[pipeline] = run_gather_jobs(make_jobs(), pipeline=pipeline)
                walls[pipeline].append(time.perf_counter() - t0)

        for key, seq_v in outs[False].items():
            seq_leaves = jax.tree_util.tree_leaves(seq_v)
            pipe_leaves = jax.tree_util.tree_leaves(outs[True][key])
            if not all(np.array_equal(a, b) for a, b in zip(seq_leaves, pipe_leaves)):
                print(
                    f"bench: PARITY-MISMATCH overlap {key}: pipelined merge != "
                    f"sequential merge",
                    file=sys.stderr,
                )

        seq_s, pipe_s = min(walls[False]), min(walls[True])
        frac = (seq_s - pipe_s) / seq_s if seq_s else 0.0
        wire_ms = (3 * BASE_RTT_S + state_bytes / BYTES_PER_S) * 1e3
        _emit(
            "sync_gather_sequential_ms",
            round(seq_s * 1e3, 1),
            f"ms wall for the {JOBS}-sketch gather+merge sequence, sequential "
            f"schedule (simulated {RANKS}-rank pod, {state_bytes / 1024:.0f} KiB "
            f"state -> {wire_ms:.0f} ms DCN-shaped wire per job, min-of-3, "
            f"{platform})",
        )
        _emit(
            "sync_gather_pipelined_ms",
            round(pipe_s * 1e3, 1),
            f"ms wall, same jobs through the run_gather_jobs issue/fold "
            f"pipeline — fold i overlaps job i+1's wire time ({platform})",
        )
        _emit(
            "sync_chunk_overlap_frac",
            round(frac, 3),
            f"fraction of the sequential wall recovered by overlapping folds "
            f"with wire time ({seq_s * 1e3:.0f} -> {pipe_s * 1e3:.0f} ms; "
            f"acceptance >= 0.30, {platform})",
        )
        if frac < 0.30:
            print(
                f"bench: PARITY-MISMATCH overlap acceptance: recovered fraction "
                f"{frac:.3f} < 0.30 ({seq_s * 1e3:.0f} -> {pipe_s * 1e3:.0f} ms)",
                file=sys.stderr,
            )
    except Exception as err:  # pragma: no cover
        print(f"bench: overlap failed: {err}", file=sys.stderr)


def _phase_fleet_bytes(jax, platform) -> None:
    """Delta fleet publishing (ISSUE 16): steady-state wire bytes per
    publish cadence, delta vs full, at three simulated fleet scales. Every
    host holds the stated production shape — a large mostly-idle state (a
    0.01-eps QuantileSketch of a seeded latency distribution) next to a
    small hot one (an Accuracy that absorbs a batch every cadence) — and
    publishes through a real ``FleetPublisher`` into a real ``Aggregator``,
    one delta-enabled fleet and one full-view twin fed the identical
    updates. Acceptance: steady-state delta bytes <= 10% of the full-view
    bytes at every scale, with each host's held view in the delta
    aggregator bit-equal to the full twin's (the re-base protocol never
    traded bytes for correctness)."""
    _stamp("fleet_bytes start")
    import numpy as np
    import jax.numpy as jnp

    import metrics_tpu as mt
    from metrics_tpu.fleet import Aggregator, FleetPublisher
    from metrics_tpu.fleet.wire import _checksum_tree

    SCALES = (8, 32, 128)
    CADENCES = 5  # steady-state cadences after the first (full) publish

    def make_coll():
        return mt.MetricCollection(
            {
                "lat": mt.QuantileSketch(eps=0.01, k=16384, levels=4, quantiles=(0.5, 0.99)),
                "acc": mt.Accuracy(num_classes=4),
            }
        )

    rng = np.random.default_rng(61)

    def acc_batch():
        return (
            jnp.asarray(rng.random((16, 4), dtype=np.float32)),
            jnp.asarray(rng.integers(0, 4, 16).astype(np.int32)),
        )

    try:
        for n_hosts in SCALES:
            agg_delta = Aggregator(make_coll(), node_id=f"pod-delta-{n_hosts}")
            agg_full = Aggregator(make_coll(), node_id=f"pod-full-{n_hosts}")
            delta_bytes, full_bytes = [], []

            hosts = []
            for h in range(n_hosts):
                coll = make_coll()
                coll["lat"].update(jnp.asarray(rng.lognormal(0, 2, 4096).astype(np.float32)))
                coll["acc"].update(*acc_batch())
                hosts.append(
                    (
                        coll,
                        FleetPublisher(
                            coll,
                            lambda blob: (delta_bytes.append(len(blob)) or agg_delta.ingest(blob)),
                            host_id=f"h{h}",
                            start=False,
                            delta=True,
                        ),
                        FleetPublisher(
                            coll,
                            lambda blob: (full_bytes.append(len(blob)) or agg_full.ingest(blob)),
                            host_id=f"h{h}",
                            start=False,
                            delta=False,
                        ),
                    )
                )

            for _coll, pub_d, pub_f in hosts:  # cadence 0: both ship full
                pub_d.publish_now()
                pub_f.publish_now()
            first_full = sum(full_bytes)
            delta_bytes.clear()
            full_bytes.clear()
            for _c in range(CADENCES):  # steady state: only `acc` moves
                for coll, pub_d, pub_f in hosts:
                    coll["acc"].update(*acc_batch())
                    pub_d.publish_now()
                    pub_f.publish_now()

            delta_per_cad = sum(delta_bytes) / CADENCES
            full_per_cad = sum(full_bytes) / CADENCES
            ratio = delta_per_cad / full_per_cad if full_per_cad else 1.0
            for h in range(n_hosts):
                with agg_delta._lock:
                    dd = _checksum_tree(agg_delta._views[f"h{h}"]["payload"])
                with agg_full._lock:
                    df = _checksum_tree(agg_full._views[f"h{h}"]["payload"])
                if dd != df:
                    print(
                        f"bench: PARITY-MISMATCH fleet_bytes h{h}@{n_hosts}: delta "
                        f"aggregator's held view != full twin's",
                        file=sys.stderr,
                    )
            _emit(
                f"fleet_delta_bytes_ratio_{n_hosts}hosts",
                round(ratio, 4),
                f"steady-state delta bytes / full-view bytes per publish cadence "
                f"({n_hosts} hosts x {CADENCES} cadences, "
                f"{first_full / n_hosts / 1024:.0f} KiB/host full view, "
                f"{delta_per_cad / 1024:.0f} vs {full_per_cad / 1024:.0f} KiB/cadence "
                f"fleet-wide; acceptance <= 0.10, {platform})",
            )
            if ratio > 0.10:
                print(
                    f"bench: PARITY-MISMATCH fleet_bytes acceptance: delta/full "
                    f"ratio {ratio:.4f} > 0.10 at {n_hosts} hosts",
                    file=sys.stderr,
                )
    except Exception as err:  # pragma: no cover
        print(f"bench: fleet_bytes failed: {err}", file=sys.stderr)


def _phase_sliced(jax, platform) -> None:
    """Sliced multi-tenant engine (ISSUE 19): per-cohort metrics via ONE
    segment-reduce update. Part 1 pins the O(batch) claim — the compiled
    update wall of a guarded sliced Accuracy at K=256 must stay within 3x
    of K=1 (the work is per-row deltas + one scatter; K only sizes the
    rings). Part 2 extends the delta-publishing points: a host whose state
    is a large idle sketch next to a hot SlicedMetric publishes deltas at
    K=16 and K=256 — the (K+2,) rings are single leaves whose steady-state
    sparsity zlib flattens, so delta bytes grow far sub-linearly in K
    (acceptance: 16x more slices costs <= 3x steady-state delta bytes, and
    delta stays <= 25% of the full view at both K)."""
    _stamp("sliced start")
    import numpy as np
    import jax.numpy as jnp

    import metrics_tpu as mt

    B, C = 4096, 4
    rng = np.random.default_rng(19)
    preds = jnp.asarray(rng.random((B, C), dtype=np.float32))
    target = jnp.asarray(rng.integers(0, C, B).astype(np.int32))

    try:
        walls = {}
        for K in (1, 16, 256):
            mdef = mt.sliced_functionalize(
                mt.Accuracy(num_classes=C, on_invalid="warn"), num_slices=K
            )
            ids = jnp.asarray(rng.integers(0, K, B).astype(np.int32))
            step = jax.jit(
                lambda s, p, t, i, _m=mdef: _m.update(s, p, t, slice_ids=i),
                donate_argnums=0,
            )
            state = step(mdef.init(), preds, target, ids)  # compile + warm
            jax.block_until_ready(jax.tree_util.tree_leaves(state))
            iters = 30
            start = time.perf_counter()
            for _ in range(iters):
                state = step(state, preds, target, ids)
            jax.block_until_ready(jax.tree_util.tree_leaves(state))
            walls[K] = (time.perf_counter() - start) / iters * 1e3
            _emit(
                f"sliced_update_ms_k{K}",
                round(walls[K], 4),
                f"ms/update (guarded Accuracy x {K} slices in one segment-reduce "
                f"graph, B={B}, {platform})",
            )
        ratio = walls[256] / walls[1] if walls[1] else float("inf")
        _emit(
            "sliced_update_k256_vs_k1",
            round(ratio, 4),
            f"K=256 update wall / K=1 update wall (acceptance <= 3.0, {platform})",
        )
        if ratio > 3.0:
            print(
                f"bench: PARITY-MISMATCH sliced acceptance: K=256 update wall is "
                f"{ratio:.2f}x K=1 (budget 3.0x) — the segment-reduce is no longer "
                f"O(batch)",
                file=sys.stderr,
            )
    except Exception as err:  # pragma: no cover
        print(f"bench: sliced update scaling failed: {err}", file=sys.stderr)

    try:
        from metrics_tpu.fleet import Aggregator, FleetPublisher

        CADENCES = 5
        per_k = {}
        for K in (16, 256):
            def make_coll(k=K):
                return mt.MetricCollection(
                    {
                        "lat": mt.QuantileSketch(
                            eps=0.01, k=16384, levels=4, quantiles=(0.5, 0.99)
                        ),
                        "acc": mt.SlicedMetric(mt.Accuracy(num_classes=C), num_slices=k),
                    }
                )

            def hot_batch(k=K):
                # steady-state traffic touches a handful of cohorts
                return (
                    jnp.asarray(rng.random((16, C), dtype=np.float32)),
                    jnp.asarray(rng.integers(0, C, 16).astype(np.int32)),
                    jnp.asarray(rng.integers(0, min(k, 4), 16).astype(np.int32)),
                )

            agg_d = Aggregator(make_coll(), node_id=f"pod-sliced-d{K}")
            agg_f = Aggregator(make_coll(), node_id=f"pod-sliced-f{K}")
            coll = make_coll()
            coll["lat"].update(jnp.asarray(rng.lognormal(0, 2, 4096).astype(np.float32)))
            p, t, i = hot_batch()
            coll["acc"].update(p, t, slice_ids=i)
            d_bytes, f_bytes = [], []
            pub_d = FleetPublisher(
                coll, lambda b: (d_bytes.append(len(b)) or agg_d.ingest(b)),
                host_id="h0", start=False, delta=True,
            )
            pub_f = FleetPublisher(
                coll, lambda b: (f_bytes.append(len(b)) or agg_f.ingest(b)),
                host_id="h0", start=False, delta=False,
            )
            pub_d.publish_now()  # cadence 0 ships the full view
            pub_f.publish_now()
            d_bytes.clear(), f_bytes.clear()
            for _c in range(CADENCES):  # steady state: only `acc` rings move
                p, t, i = hot_batch()
                coll["acc"].update(p, t, slice_ids=i)
                pub_d.publish_now()
                pub_f.publish_now()
            delta_cad = sum(d_bytes) / CADENCES
            full_cad = sum(f_bytes) / CADENCES
            per_k[K] = delta_cad
            _emit(
                f"sliced_fleet_delta_bytes_k{K}",
                round(delta_cad, 1),
                f"steady-state delta bytes/cadence (idle 0.01-eps sketch + hot "
                f"{K}-slice Accuracy; full view {full_cad / 1024:.1f} KiB/cadence; "
                f"acceptance <= 25% of full, {platform})",
            )
            if full_cad and delta_cad / full_cad > 0.25:
                print(
                    f"bench: PARITY-MISMATCH sliced fleet acceptance: delta/full "
                    f"{delta_cad / full_cad:.3f} > 0.25 at K={K}",
                    file=sys.stderr,
                )
        growth = per_k[256] / per_k[16] if per_k.get(16) else float("inf")
        _emit(
            "sliced_fleet_delta_growth_k256_vs_k16",
            round(growth, 4),
            f"steady-state delta bytes K=256 / K=16 (16x more slices; "
            f"acceptance <= 3.0, {platform})",
        )
        if growth > 3.0:
            print(
                f"bench: PARITY-MISMATCH sliced fleet acceptance: delta payload "
                f"grew {growth:.2f}x from K=16 to K=256 (budget 3.0x for 16x K)",
                file=sys.stderr,
            )
    except Exception as err:  # pragma: no cover
        print(f"bench: sliced fleet bytes failed: {err}", file=sys.stderr)


_PHASES = {
    "headline": (_phase_headline, 420),
    "auroc": (_phase_auroc, 240),
    "ssim": (_phase_ssim, 150),
    "retrieval": (_phase_retrieval, 150),
    "vsref": (_phase_vsref, 240),
    "detection": (_phase_detection, 120),
    "bucketed_rank": (_phase_bucketed_rank, 420),
    "guard": (_phase_guard, 300),
    "checkpoint": (_phase_checkpoint, 240),
    "sync": (_phase_sync, 150),
    "streaming": (_phase_streaming, 300),
    "compactor": (_phase_compactor, 420),
    "serving": (_phase_serving, 300),
    "coldstart": (_phase_coldstart, 420),
    "async_sync": (_phase_async_sync, 300),
    "obs": (_phase_obs, 300),
    "transport": (_phase_transport, 300),
    "overlap": (_phase_overlap, 240),
    "fleet_bytes": (_phase_fleet_bytes, 420),
    "sliced": (_phase_sliced, 420),
}

_HEADLINE_METRIC = "fused_collection_step_ms"


def _run_phase_child(name: str) -> None:
    import jax

    platform = jax.devices()[0].platform
    _PHASES[name][0](jax, platform)


def _cpu_env() -> dict:
    """Child env for CPU runs that cannot touch the TPU tunnel.

    JAX_PLATFORMS=cpu alone is NOT enough: the environment injects an
    axon sitecustomize via PYTHONPATH that initializes jax (and dials the
    tunnel) at interpreter startup, so with a wedged tunnel even CPU
    children hang at ``import jax``. Stripping the axon entry from
    PYTHONPATH gives a clean interpreter.
    """
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    parts = [e for e in env.get("PYTHONPATH", "").split(os.pathsep) if e and "axon" not in e]
    env["PYTHONPATH"] = os.pathsep.join(parts)
    return env


_HIST_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)), "BENCH_HISTORY.json")


def _load_history() -> dict:
    try:
        with open(_HIST_PATH) as f:
            return json.load(f)
    except Exception:
        return {}


def _platform_key(unit: str) -> str:
    # tpu/axon first: the vsref unit strings always mention "torch-cpu" for
    # the reference side, so a cpu-first match would misfile TPU runs
    u = unit.lower()
    if "tpu" in u or "axon" in u:
        return "tpu"
    if "cpu" in u:
        return "cpu"
    return "other"


def _annotate_vs_prev(line: str, history: dict, measured: dict) -> str:
    """Attach ``vs_prev`` (previous same-platform value / current value;
    >1 = faster than last round) to an emitted JSON line, record the new
    value for the history update, and flag >15% drifts on stderr —
    VERDICT r4 weak #3: perf numbers that drift untracked stop being
    numbers."""
    try:
        rec = json.loads(line)
        plat = _platform_key(rec.get("unit", ""))
        prev = history.get(plat, {}).get(rec["metric"])
        rec["vs_prev"] = round(prev / rec["value"], 3) if prev and rec["value"] else None
        measured.setdefault(plat, {})[rec["metric"]] = rec["value"]
        if rec["vs_prev"] is not None and abs(rec["vs_prev"] - 1.0) > 0.15:
            direction = "faster" if rec["vs_prev"] > 1 else "SLOWER"
            print(
                f"bench: DRIFT {rec['metric']} ({plat}): {prev} -> {rec['value']} "
                f"({rec['vs_prev']}x, {direction} than last round)",
                file=sys.stderr,
            )
        return json.dumps(rec)
    except Exception:
        return line


def _write_history(history: dict, measured: dict) -> None:
    """Merge this run's same-platform numbers over the stored ones so the
    next round's ``vs_prev`` compares like-for-like."""
    for plat, metrics in measured.items():
        history.setdefault(plat, {}).update(metrics)
    try:
        with open(_HIST_PATH, "w") as f:
            json.dump(history, f, indent=1, sort_keys=True)
            f.write("\n")
    except Exception as err:  # pragma: no cover
        print(f"bench: history write failed: {err}", file=sys.stderr)


def main() -> None:
    platform = _probe_default_backend()
    if platform is None:
        print("bench: default backend unusable; falling back to CPU", file=sys.stderr)
        env = _cpu_env()
    else:
        env = dict(os.environ)

    history = _load_history()
    measured: dict = {}
    headline_line = None
    consecutive_timeouts = 0
    for name, (_, budget) in _PHASES.items():
        if consecutive_timeouts >= 2:
            # tunnel is almost certainly wedged; stop burning whole budgets
            print(f"bench: skipping phase {name} (tunnel looks wedged)", file=sys.stderr)
            continue
        _stamp(f"phase {name} start")
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--phase", name],
                timeout=budget,
                capture_output=True,
                text=True,
                env=env,
            )
        except subprocess.TimeoutExpired:
            print(f"bench: phase {name} exceeded {budget}s; skipped", file=sys.stderr)
            consecutive_timeouts += 1
            continue
        consecutive_timeouts = 0
        if proc.returncode != 0:
            print(f"bench: phase {name} rc={proc.returncode}: {proc.stderr.strip()[-400:]}", file=sys.stderr)
        else:
            # phase bodies swallow their own exceptions and exit 0 — their
            # "bench: ... failed" diagnostics live on stderr and must survive
            for eline in proc.stderr.splitlines():
                if eline.startswith("bench:"):
                    print(eline, file=sys.stderr)
        for line in proc.stdout.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            if f'"{_HEADLINE_METRIC}"' in line:
                headline_line = line  # the driver's tracked number prints last
            else:
                print(_annotate_vs_prev(line, history, measured))

    if headline_line is None:
        # the headline died (wedged tunnel mid-run, or a slow CPU box):
        # a number must still land — retry on tunnel-free CPU
        print("bench: headline missing; retrying on CPU", file=sys.stderr)
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--phase", "headline"],
                timeout=480,
                capture_output=True,
                text=True,
                env=_cpu_env(),
            )
            for line in proc.stdout.splitlines():
                if f'"{_HEADLINE_METRIC}"' in line:
                    headline_line = line.strip()
        except subprocess.TimeoutExpired:
            pass
    if headline_line is not None:
        print(_annotate_vs_prev(headline_line, history, measured))
    _write_history(history, measured)


def _bench_device_headline(jax, jnp, np, entry, platform: str) -> None:
    """The fused step timed by the on-device loop (pure chip time, no tunnel).

    The legacy headline measures host-side enqueue throughput for
    comparability with earlier rounds; this is the honest per-step device
    latency of the same program.
    """
    try:
        step, (state, _, _) = entry()
        B, C = 8192, 16
        rng = np.random.default_rng(0)
        preds = jnp.asarray(rng.random((B, C)), jnp.float32)
        target = jnp.asarray(rng.integers(0, C, B), jnp.int32)

        def step_iter(carry):
            st, acc = carry
            st, metrics = step(st, preds, target)
            return st, acc + metrics["f1"]  # consumed -> compute isn't DCE'd

        # ~4us/step on the chip needs many iterations to clear tunnel noise;
        # the CPU fallback is ~100x slower per step, so scale down to fit
        # the phase budget
        iters = 32768 if platform == "tpu" else 1024
        ms = _device_loop_ms(jax, step_iter, (dict(state), jnp.asarray(0.0)), iters)
        _emit(
            "fused_collection_step_device_ms",
            round(ms, 4),
            f"ms/step on-device (update+4-metric compute, B=8192, C=16, {platform})",
            round(2.0 / ms, 2) if ms > 0 else None,
        )
    except Exception as err:  # pragma: no cover
        print(f"bench: device headline failed: {err}", file=sys.stderr)


def _bench_headline(jax, jnp, np, entry, platform: str) -> dict:
    step, (state, _, _) = entry()

    B, C = 8192, 16
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.random((B, C)), jnp.float32)
    target = jnp.asarray(rng.integers(0, C, B), jnp.int32)

    jit_step = jax.jit(step, donate_argnums=0)

    # warmup / compile
    state_w, metrics = jit_step(dict(state), preds, target)
    jax.block_until_ready(metrics)

    iters = 50
    st = state_w  # warmup donated `state`'s buffers; continue from its output
    start = time.perf_counter()
    for _ in range(iters):
        st, metrics = jit_step(st, preds, target)
    jax.block_until_ready(metrics)
    elapsed_ms = (time.perf_counter() - start) / iters * 1e3

    target_ms = 2.0  # BASELINE.md north-star budget for a fused collection step
    return {
        "metric": "fused_collection_step_ms",
        "value": round(elapsed_ms, 4),
        "unit": f"ms/step (update+4-metric compute, B=8192, C=16, {platform})",
        "vs_baseline": round(target_ms / elapsed_ms, 2),
    }


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--phase":
        _run_phase_child(sys.argv[2])
    else:
        main()

"""Benchmark: fused MetricCollection step (update + compute) on one chip.

Headline number tracked against the BASELINE.md north star: the reference's
target is a ``MetricCollection([Accuracy, F1, ...]).compute()`` under 2 ms
(BASELINE.json; the reference itself publishes no absolute numbers — see
BASELINE.md). ``vs_baseline`` is the speedup vs that 2 ms budget (>1 = faster
than target).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Robustness: round 1 emitted no number because the environment-pinned ``axon``
TPU backend died during init; a later run showed init can also *hang*
indefinitely. So the backend is probed in a subprocess with a hard timeout
(a hang can't be cancelled once it's in-process), retried, and on failure the
bench falls back to CPU — a number always lands, and the JSON unit string
records which platform produced it.
"""
import json
import os
import subprocess
import sys
import time

_PROBE_SRC = "import jax; print(jax.devices()[0].platform)"


def _probe_default_backend(timeout_s: float = 150.0, attempts: int = 2):
    """Check, in a throwaway subprocess, that the default backend comes up.

    A *hang* (timeout) forces the CPU fallback immediately: a backend that
    hung once can hang again in-process, where nothing can cancel it and no
    JSON line would ever be emitted. Only clean-but-failed probes are retried.
    """
    for attempt in range(attempts):
        try:
            proc = subprocess.run(
                [sys.executable, "-c", _PROBE_SRC],
                timeout=timeout_s,
                capture_output=True,
                text=True,
            )
        except subprocess.TimeoutExpired:
            print(f"bench: backend probe hung >{timeout_s}s; not retrying", file=sys.stderr)
            return None
        if proc.returncode == 0 and proc.stdout.strip():
            return proc.stdout.strip().splitlines()[-1]  # plugin chatter may precede it
        print(
            f"bench: backend probe attempt {attempt + 1} failed rc={proc.returncode}: "
            f"{proc.stderr.strip()[-500:]}",
            file=sys.stderr,
        )
    return None


def _init_backend():
    platform = _probe_default_backend()
    if platform is None:
        print("bench: default backend unusable; falling back to CPU", file=sys.stderr)
        os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    if platform is None:
        from metrics_tpu.utilities.backend import force_cpu_backend

        force_cpu_backend()
        platform = jax.devices()[0].platform
    return jax, platform


def main() -> None:
    jax, platform = _init_backend()
    import jax.numpy as jnp
    import numpy as np

    from __graft_entry__ import entry

    step, (state, _, _) = entry()

    B, C = 8192, 16
    rng = np.random.default_rng(0)
    preds = jnp.asarray(rng.random((B, C)), jnp.float32)
    target = jnp.asarray(rng.integers(0, C, B), jnp.int32)

    jit_step = jax.jit(step, donate_argnums=0)

    # warmup / compile
    state_w, metrics = jit_step(dict(state), preds, target)
    jax.block_until_ready(metrics)

    iters = 50
    st = state_w  # warmup donated `state`'s buffers; continue from its output
    start = time.perf_counter()
    for _ in range(iters):
        st, metrics = jit_step(st, preds, target)
    jax.block_until_ready(metrics)
    elapsed_ms = (time.perf_counter() - start) / iters * 1e3

    target_ms = 2.0  # BASELINE.md north-star budget for a fused collection step
    print(
        json.dumps(
            {
                "metric": "fused_collection_step_ms",
                "value": round(elapsed_ms, 4),
                "unit": f"ms/step (update+4-metric compute, B=8192, C=16, {platform})",
                "vs_baseline": round(target_ms / elapsed_ms, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
